"""MQ2007 learning-to-rank dataset (LETOR 4.0).

Reference: python/paddle/v2/dataset/mq2007.py (MQ2007.rar, svmlight-style
'rel qid:N 1:f 2:f ... #docid' lines, 46 features; pointwise / pairwise /
listwise sample generators over per-query groups). The .rar needs an
extractor (`unrar`/`bsdtar`/`7z` — python rarfile is not available here);
the LETOR text parser itself is fully implemented and unit-tested on
fixtures, with a synthetic fallback when offline.
"""

from __future__ import annotations

import os
import subprocess
from typing import Iterator, List, Optional, Tuple

import numpy as np

from paddle_tpu.dataset import common

URL = ("http://www.bigdatalab.ac.cn/benchmark/upload/download_source/"
       "7b6dbbe2-842c-11e4-a536-bcaec51b9163_MQ2007.rar")
MD5 = "7be1640ae95c6408dab0ae7207bdc706"

FEATURE_DIM = 46


def parse_letor_line(line: str) -> Optional[Tuple[int, int, np.ndarray]]:
    """'rel qid:N 1:f ... 46:f #comment' -> (relevance, query_id, features)."""
    body = line.split("#", 1)[0].strip()
    if not body:
        return None
    parts = body.split()
    if len(parts) != FEATURE_DIM + 2:
        return None
    rel = int(parts[0])
    qid = int(parts[1].split(":")[1])
    feats = np.asarray([float(p.split(":")[1]) for p in parts[2:]],
                       np.float32)
    return rel, qid, feats


def group_by_query(lines) -> Iterator[List[Tuple[float, np.ndarray]]]:
    """Group consecutive lines by qid -> list of (relevance, features),
    sorted best-first within the group (the reference's _correct_ranking_)."""
    cur_qid, group = None, []
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        parsed = parse_letor_line(line)
        if parsed is None:
            continue
        rel, qid, feats = parsed
        if cur_qid is not None and qid != cur_qid and group:
            group.sort(key=lambda x: -x[0])
            yield group
            group = []
        cur_qid = qid
        group.append((float(rel), feats))
    if group:
        group.sort(key=lambda x: -x[0])
        yield group


def gen_point(group):
    """Pointwise: (relevance, features) per doc."""
    for rel, feats in group:
        yield rel, feats


def gen_pair(group, partial_order: str = "full"):
    """Pairwise: (left_feats, right_feats, 1.0) with left ranked higher."""
    n = len(group)
    idx_pairs = ([(i, i + 1) for i in range(n - 1)]
                 if partial_order == "neighbour"
                 else [(i, j) for i in range(n) for j in range(i + 1, n)])
    for i, j in idx_pairs:
        li, fi = group[i]
        lj, fj = group[j]
        if li > lj:
            yield fi, fj, 1.0
        elif li < lj:
            yield fj, fi, 1.0


def gen_list(group):
    """Listwise: the whole per-query group as [(rel, feats), ...]."""
    yield list(group)


_GENERATORS = {"pointwise": gen_point, "pairwise": gen_pair,
               "listwise": gen_list}


def _extract_rar(rar_path: str) -> Optional[str]:
    """Try external extractors; returns the extraction dir or None."""
    out_dir = os.path.dirname(rar_path)
    marker = os.path.join(out_dir, "MQ2007")
    if os.path.isdir(marker):
        return out_dir
    for cmd in (["unrar", "x", "-o+", rar_path, out_dir + "/"],
                ["bsdtar", "-xf", rar_path, "-C", out_dir],
                ["7z", "x", "-y", f"-o{out_dir}", rar_path]):
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=600)
            if r.returncode == 0 and os.path.isdir(marker):
                return out_dir
        except Exception:
            continue
    return None


def _real_reader(fold_file: str, fmt: str):
    gen = _GENERATORS[fmt]

    def reader():
        with open(fold_file) as f:
            for group in group_by_query(f):
                yield from gen(group)

    return reader


def _synthetic_queries(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURE_DIM)
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.randn(n_docs, FEATURE_DIM).astype(np.float32)
        scores = feats @ w + 0.5 * rng.randn(n_docs)
        rels = np.digitize(scores, np.percentile(scores, [33, 66]))
        group = sorted(((float(rels[i]), feats[i]) for i in range(n_docs)),
                       key=lambda x: -x[0])
        yield group


def _synth_reader(n_queries, seed, fmt):
    gen = _GENERATORS[fmt]

    def reader():
        for group in _synthetic_queries(n_queries, seed):
            yield from gen(group)

    return reader


def _fold_path(split: str) -> Optional[str]:
    try:
        rar = common.download(URL, "MQ2007", MD5)
        root = _extract_rar(rar)
        if root is None:
            return None
        path = os.path.join(root, "MQ2007", "Fold1", f"{split}.txt")
        return path if os.path.exists(path) else None
    except Exception:
        return None


def train(format: str = "pairwise"):
    fold = _fold_path("train")
    if fold is None:
        return _synth_reader(512, 90, format)
    return _real_reader(fold, format)


def test(format: str = "pairwise"):
    fold = _fold_path("test")
    if fold is None:
        return _synth_reader(64, 91, format)
    return _real_reader(fold, format)


def fetch() -> None:
    common.download(URL, "MQ2007", MD5)
