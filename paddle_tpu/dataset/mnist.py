"""MNIST dataset (reference: v2/dataset/mnist.py).

Samples: (image: float32[784] scaled to [-1,1], label: int). Falls back to a
deterministic synthetic digit set when offline (no egress in CI).
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from paddle_tpu.dataset import common

URL_PREFIX = "https://storage.googleapis.com/cvdf-datasets/mnist/"
TRAIN_IMAGE = ("train-images-idx3-ubyte.gz", "f68b3c2dcbeaaa9fbdd348bbdeb94873")
TRAIN_LABEL = ("train-labels-idx1-ubyte.gz", "d53e105ee54ea40749a09fcbcd1e9432")
TEST_IMAGE = ("t10k-images-idx3-ubyte.gz", "9fb629c4189551a2d022fa330f9573f3")
TEST_LABEL = ("t10k-labels-idx1-ubyte.gz", "ec29112dd5afa0611ce80d1b7f02629c")


def _parse_idx(images_path: str, labels_path: str):
    with gzip.open(images_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    with gzip.open(labels_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    images = images.astype(np.float32) / 255.0 * 2.0 - 1.0
    return images, labels.astype(np.int64)


def _synthetic(n: int, seed: int):
    """Deterministic class-structured fake digits: each class k is a distinct
    smoothed template + noise, so simple models actually learn. Templates are
    seed-independent so train/test share the class structure."""
    templates = np.random.RandomState(1234).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n)
    images = templates[labels] * 0.5 + rng.randn(n, 784).astype(np.float32) * 0.3
    images = np.tanh(images)
    return images.astype(np.float32), labels.astype(np.int64)


def _reader(images, labels):
    def reader():
        for img, lab in zip(images, labels):
            yield img, int(lab)

    return reader


def _load(image_meta, label_meta, synth_n, synth_seed):
    try:
        img_path = common.download(URL_PREFIX + image_meta[0], "mnist", image_meta[1])
        lab_path = common.download(URL_PREFIX + label_meta[0], "mnist", label_meta[1])
        return _parse_idx(img_path, lab_path)
    except Exception:
        return _synthetic(synth_n, synth_seed)


def train():
    images, labels = _load(TRAIN_IMAGE, TRAIN_LABEL, 8192, 0)
    return _reader(images, labels)


def test():
    images, labels = _load(TEST_IMAGE, TEST_LABEL, 1024, 1)
    return _reader(images, labels)
