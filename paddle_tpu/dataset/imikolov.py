"""PTB (imikolov) language-model dataset — n-grams or seq pairs.

Reference: python/paddle/v2/dataset/imikolov.py (simple-examples.tgz,
freq-sorted dict over train+valid with one <s>/<e> counted per line and
<unk> last, NGRAM sliding windows / SEQ src-trg pairs). Real pipeline with
a synthetic fallback when offline.
"""

from __future__ import annotations

import collections
import tarfile
from typing import Dict, Iterator

import numpy as np

from paddle_tpu.dataset import common

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"

TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
VALID_FILE = "./simple-examples/data/ptb.valid.txt"


class DataType:
    NGRAM = 1
    SEQ = 2


def word_count(lines: Iterator, word_freq=None) -> Dict[str, int]:
    """Count words plus one <s>/<e> per line (sentence markers)."""
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict_from_files(trainf, testf, min_word_freq: int) -> Dict[str, int]:
    word_freq = word_count(testf, word_count(trainf))
    word_freq.pop("<unk>", None)  # re-added as the last index below
    kept = [(w, f) for w, f in word_freq.items() if f > min_word_freq]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def build_dict(min_word_freq: int = 50) -> Dict[str, int]:
    try:
        path = common.download(URL, "imikolov", MD5)
        with tarfile.open(path) as tf:
            return build_dict_from_files(tf.extractfile(TRAIN_FILE),
                                         tf.extractfile(VALID_FILE),
                                         min_word_freq)
    except Exception:
        d = {f"w{i}": i for i in range(1999)}
        d["<unk>"] = 1999
        return d


def parse_lines(lines, word_idx: Dict[str, int], n: int, data_type: int):
    """Core parse: NGRAM -> sliding ID windows over '<s> line <e>';
    SEQ -> (<s>+ids, ids+<e>) pairs, skipping sequences longer than n."""
    unk = word_idx["<unk>"]
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="ignore")
        words = line.strip().split()
        if data_type == DataType.NGRAM:
            assert n > -1, "Invalid gram length"
            toks = ["<s>"] + words + ["<e>"]
            if len(toks) >= n:
                ids = [word_idx.get(w, unk) for w in toks]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n:i])
        elif data_type == DataType.SEQ:
            ids = [word_idx.get(w, unk) for w in words]
            src = [word_idx["<s>"]] + ids
            trg = ids + [word_idx["<e>"]]
            if n > 0 and len(src) > n:
                continue
            yield src, trg
        else:
            raise ValueError(f"unknown data type {data_type}")


def _real_reader(filename: str, word_idx, n, data_type):
    def reader():
        path = common.download(URL, "imikolov", MD5)
        with tarfile.open(path) as tf:
            yield from parse_lines(tf.extractfile(filename), word_idx, n,
                                   data_type)

    return reader


def _synth_reader(word_idx, n, data_type, count, seed):
    """Markov-ish synthetic n-grams / sequences (offline CI fallback)."""
    def reader():
        rng = np.random.RandomState(seed)
        dim = len(word_idx)
        trans = rng.randint(0, dim, size=(dim,))
        for _ in range(count):
            start = int(rng.randint(dim))
            gram = [start]
            for _ in range(max(n - 1, 4)):
                gram.append(int((trans[gram[-1]] + rng.randint(3)) % dim))
            if data_type == DataType.NGRAM:
                yield tuple(gram[:n])
            else:
                yield gram, gram[1:] + [gram[0]]

    return reader


def train(word_idx: Dict[str, int], n: int, data_type: int = DataType.NGRAM):
    try:
        common.download(URL, "imikolov", MD5)
    except Exception:
        return _synth_reader(word_idx, n, data_type, 4096, 20)
    return _real_reader(TRAIN_FILE, word_idx, n, data_type)


def test(word_idx: Dict[str, int], n: int, data_type: int = DataType.NGRAM):
    try:
        common.download(URL, "imikolov", MD5)
    except Exception:
        return _synth_reader(word_idx, n, data_type, 512, 21)
    return _real_reader(VALID_FILE, word_idx, n, data_type)


def fetch() -> None:
    common.download(URL, "imikolov", MD5)
