"""PTB language-model n-grams (reference: v2/dataset/imikolov.py)."""
import numpy as np


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(2000)}


def train(word_idx, n):
    dim = len(word_idx)

    def reader():
        rng = np.random.RandomState(20)
        # markov-ish synthetic n-grams
        trans = rng.randint(0, dim, size=(dim,))
        for _ in range(4096):
            start = int(rng.randint(dim))
            gram = [start]
            for _ in range(n - 1):
                gram.append(int((trans[gram[-1]] + rng.randint(3)) % dim))
            yield tuple(gram)

    return reader


def test(word_idx, n):
    def reader():
        rng = np.random.RandomState(21)
        dim = len(word_idx)
        trans = rng.randint(0, dim, size=(dim,))
        for _ in range(512):
            start = int(rng.randint(dim))
            gram = [start]
            for _ in range(n - 1):
                gram.append(int((trans[gram[-1]] + rng.randint(3)) % dim))
            yield tuple(gram)

    return reader
