// Shared recordio wire helpers: 8-byte little-endian u64 length prefix
// (must match paddle_tpu/master/recordio.py struct "<Q").
#pragma once

#include <cstdint>
#include <cstdio>

namespace ptn {

inline bool read_u64(FILE* f, uint64_t* out) {
  unsigned char b[8];
  if (fread(b, 1, 8, f) != 8) return false;
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | b[i];
  *out = v;
  return true;
}

inline bool write_u64(FILE* f, uint64_t v) {
  unsigned char b[8];
  for (int i = 0; i < 8; ++i) {
    b[i] = static_cast<unsigned char>(v & 0xff);
    v >>= 8;
  }
  return fwrite(b, 1, 8, f) == 8;
}

}  // namespace ptn
