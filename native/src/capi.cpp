// C inference ABI over merged models.
//
// Reference analog: paddle/capi — the pure-C surface embedded apps link
// against (paddle_gradient_machine_create_for_inference_with_parameters,
// _forward; capi/gradient_machine.h:36-112) driving the C++ engine on a
// merged single-file model.
//
// TPU-native design: TWO C surfaces share the merged-model story.
//  1. This file — the FULL-COVERAGE path: the merged model is a
//     serialized StableHLO program (paddle_tpu/export.py) and this ABI
//     hosts an embedded CPython running the PJRT-backed loader (any
//     graph jax can trace works, incl. symbolic batch).
//  2. aot_runtime.cpp — the INTERPRETER-FREE path: export_aot_program
//     translates the same traced forward into a .ptnm tensor program a
//     dependency-free C++ executor runs with no Python in the process
//     (the reference capi's embedded/Android deployment property).
// Embedders get plain float-in / float-out calls either way.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::once_flag g_init_once;

struct Model {
  PyObject* model = nullptr;  // paddle_tpu.export.MergedModel
};

void ensure_python() {
  std::call_once(g_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // drop the GIL the init thread holds, or every other embedder
      // thread deadlocks in PyGILState_Ensure
      PyEval_SaveThread();
    }
  });
}

}  // namespace

extern "C" {

// Load a merged model file. Returns a handle or nullptr.
void* ptpu_model_load(const char* path) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  Model* out = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.export");
  if (mod) {
    PyObject* loader = PyObject_GetAttrString(mod, "load_merged_model");
    if (loader) {
      PyObject* m = PyObject_CallFunction(loader, "s", path);
      if (m) {
        out = new Model();
        out->model = m;
      }
      Py_DECREF(loader);
    }
    Py_DECREF(mod);
  }
  if (!out) PyErr_Print();
  PyGILState_Release(gil);
  return out;
}

// Single dense float input -> first output. Returns 0 on success,
// -2 when out_capacity is too small (out_rows/out_cols then hold the
// required shape so the caller can resize and retry), -1 on failure.
int ptpu_infer(void* handle, const char* input_name, const float* data,
               int64_t batch, int64_t dim, float* out, int64_t out_capacity,
               int64_t* out_rows, int64_t* out_cols) {
  auto* m = static_cast<Model*>(handle);
  if (!m || !m->model) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  // build a python list-of-lists (no numpy C API dependency here; the
  // loader converts via np.asarray)
  PyObject* rows = PyList_New(batch);
  for (int64_t r = 0; r < batch; ++r) {
    PyObject* row = PyList_New(dim);
    for (int64_t c = 0; c < dim; ++c)
      PyList_SET_ITEM(row, c, PyFloat_FromDouble(data[r * dim + c]));
    PyList_SET_ITEM(rows, r, row);
  }
  PyObject* feeds = PyDict_New();
  PyDict_SetItemString(feeds, input_name, rows);
  Py_DECREF(rows);

  PyObject* outs = PyObject_CallMethod(m->model, "infer", "O", feeds);
  Py_DECREF(feeds);
  if (outs) {
    PyObject* first = PySequence_GetItem(outs, 0);
    if (first) {
      PyObject* lst =
          PyObject_CallMethod(first, "tolist", nullptr);  // ndarray -> lists
      if (lst) {
        int64_t n_rows = PySequence_Size(lst);
        int64_t n_cols = 1;
        bool flat = false;  // 1-D output: tolist() rows are floats
        if (n_rows > 0) {
          PyObject* r0 = PySequence_GetItem(lst, 0);
          if (PySequence_Check(r0)) {
            n_cols = PySequence_Size(r0);
          } else {
            flat = true;
            PyErr_Clear();
          }
          Py_DECREF(r0);
        }
        if (n_rows >= 0 && n_cols >= 0) {
          *out_rows = n_rows;
          *out_cols = flat ? 1 : n_cols;
          if (n_rows * n_cols > out_capacity) {
            rc = -2;  // caller can resize using *out_rows / *out_cols
          } else {
          for (int64_t r = 0; r < n_rows; ++r) {
            if (flat) {
              PyObject* v = PySequence_GetItem(lst, r);
              out[r] = static_cast<float>(PyFloat_AsDouble(v));
              Py_DECREF(v);
              continue;
            }
            PyObject* row = PySequence_GetItem(lst, r);
            for (int64_t c = 0; c < n_cols; ++c) {
              PyObject* v = PySequence_GetItem(row, c);
              out[r * n_cols + c] = static_cast<float>(PyFloat_AsDouble(v));
              Py_DECREF(v);
            }
            Py_DECREF(row);
          }
            rc = 0;
          }
        }
        Py_DECREF(lst);
      }
      Py_DECREF(first);
    }
    Py_DECREF(outs);
  }
  if (rc == -1 && PyErr_Occurred()) PyErr_Print();
  PyGILState_Release(gil);
  return rc;
}

// Shared-param multi-instance handle (gradient_machine.h:88 analog):
// the clone's MergedModel shares the origin's compiled executable, so N
// serving threads hold N handles over ONE weight copy. Returns nullptr
// on failure.
void* ptpu_model_create_shared(void* origin) {
  auto* m = static_cast<Model*>(origin);
  if (!m || !m->model) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  Model* out = nullptr;
  PyObject* clone =
      PyObject_CallMethod(m->model, "create_shared", nullptr);
  if (clone) {
    out = new Model();
    out->model = clone;
  } else {
    PyErr_Print();
  }
  PyGILState_Release(gil);
  return out;
}

void ptpu_model_release(void* handle) {
  auto* m = static_cast<Model*>(handle);
  if (!m) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(m->model);
  PyGILState_Release(gil);
  delete m;
}

}  // extern "C"
