// Interpreter-free C inference runtime for AOT-exported .ptnm programs.
//
// Reference analog: paddle/capi (capi/gradient_machine.h:36-112) — the
// pure-C embedded inference surface with NO Python/engine dependency in
// the process (the property that made the reference's capi deployable on
// Android, Dockerfile.android). The .ptnm program is the forward jaxpr
// translated by paddle_tpu/export.py:export_aot_program into a flat
// tensor program; this file executes it with plain C++ loops — zero
// dependencies beyond libc/libm. The CPython-hosted StableHLO path
// (capi.cpp) remains the full-coverage fallback; this runtime covers the
// dense inference graphs embedders ship (MLP/CNN/embedding + softmax
// heads; integer-id feeds ride as floats, exact below 2^24).
//
// Opcodes must stay in sync with export.py (OP_* constants).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <limits>
#include <string>
#include <vector>

namespace {

enum Op : uint32_t {
  ADD = 1, SUB = 2, MUL = 3, DIV = 4, MAX_ = 5, MIN_ = 6,
  EXP = 7, LOG = 8, TANH = 9, LOGISTIC = 10, RSQRT = 11,
  SQRT = 12, NEG = 13, ABS = 14,
  DOT = 15, BCAST = 16, RESHAPE = 17, TRANSPOSE = 18,
  RSUM = 19, RMAX = 20, CONV2D = 21, MAXPOOL = 22, SUMPOOL = 23,
  SELECT_N = 24, CLAMP = 25, CONCAT = 26, IPOW = 27, IDENT = 28,
  LT = 29, LE = 30, GT = 31, GE = 32, EQ = 33, NE = 34,
  GATHER_ROWS = 35, TRUNC = 36,
};

struct TensorMeta {
  uint8_t dtype = 0;  // 0=f32 (i32 consts are widened to f32 at load)
  std::vector<int64_t> dims;
  int64_t size() const {
    int64_t n = 1;
    for (int64_t d : dims) n *= d;
    return n;
  }
};

struct Instr {
  uint32_t opcode = 0;
  std::vector<uint32_t> ins;
  uint32_t out = 0;
  std::vector<int64_t> attrs;
};

struct Program {
  std::vector<TensorMeta> tensors;
  std::vector<std::pair<uint32_t, std::string>> inputs;  // (tensor, name)
  std::vector<uint32_t> outputs;
  std::vector<std::pair<uint32_t, std::vector<float>>> consts;
  std::vector<Instr> ops;
  // shared-param instances (ptpu_aot_create_shared) hold the same Program;
  // the last release frees it
  std::atomic<int> refs{1};
};

bool read_exact(FILE* f, void* dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

template <typename T>
bool rd(FILE* f, T* v) { return read_exact(f, v, sizeof(T)); }

constexpr int kMaxRank = 8;

// every dim positive and bounded; total element count bounded (the
// executor sizes buffers and memcpys from these — a model file from disk
// must never drive an over/under-flowed allocation)
bool sane_dims(const TensorMeta& t) {
  if (t.dims.size() > kMaxRank) return false;
  int64_t n = 1;
  for (int64_t d : t.dims) {
    if (d <= 0 || d > (int64_t(1) << 32)) return false;
    if (d > (int64_t(1) << 33) / n) return false;  // pre-divide: no overflow
    n *= d;
  }
  return true;
}

bool validate_program(const Program& p);

Program* load_program(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto fail = [&]() -> Program* { fclose(f); return nullptr; };

  char magic[4];
  if (!read_exact(f, magic, 4) || memcmp(magic, "PTNM", 4) != 0) return fail();
  uint32_t version = 0;
  if (!rd(f, &version) || version != 1) return fail();

  auto* p = new Program();
  auto die = [&]() -> Program* { delete p; fclose(f); return nullptr; };

  uint32_t nt = 0;
  if (!rd(f, &nt)) return die();
  p->tensors.resize(nt);
  for (auto& t : p->tensors) {
    uint8_t nd = 0;
    if (!rd(f, &t.dtype) || !rd(f, &nd)) return die();
    t.dims.resize(nd);
    if (nd && !read_exact(f, t.dims.data(), nd * sizeof(int64_t))) return die();
  }
  // dims must be sane BEFORE const buffers are sized from size(): a
  // negative or huge dim from disk would otherwise drive the allocation
  for (const auto& t : p->tensors)
    if (!sane_dims(t)) return die();

  uint32_t ni = 0;
  if (!rd(f, &ni)) return die();
  for (uint32_t i = 0; i < ni; ++i) {
    uint32_t tid = 0;
    uint16_t nl = 0;
    if (!rd(f, &tid) || !rd(f, &nl)) return die();
    std::string name(nl, '\0');
    if (nl && !read_exact(f, name.data(), nl)) return die();
    p->inputs.emplace_back(tid, std::move(name));
  }

  uint32_t no = 0;
  if (!rd(f, &no)) return die();
  p->outputs.resize(no);
  for (auto& o : p->outputs)
    if (!rd(f, &o)) return die();

  uint32_t nc = 0;
  if (!rd(f, &nc)) return die();
  for (uint32_t i = 0; i < nc; ++i) {
    uint32_t tid = 0;
    uint64_t nbytes = 0;
    if (!rd(f, &tid) || !rd(f, &nbytes) || tid >= nt) return die();
    const TensorMeta& m = p->tensors[tid];
    std::vector<float> vals(static_cast<size_t>(m.size()));
    if (m.dtype == 0) {
      if (nbytes != vals.size() * 4) return die();
      if (!read_exact(f, vals.data(), nbytes)) return die();
    } else {  // i32 const: widen to f32 (runtime is f32-only)
      std::vector<int32_t> raw(static_cast<size_t>(m.size()));
      if (nbytes != raw.size() * 4) return die();
      if (!read_exact(f, raw.data(), nbytes)) return die();
      for (size_t k = 0; k < raw.size(); ++k)
        vals[k] = static_cast<float>(raw[k]);
    }
    p->consts.emplace_back(tid, std::move(vals));
  }

  uint32_t nops = 0;
  if (!rd(f, &nops)) return die();
  p->ops.resize(nops);
  for (auto& op : p->ops) {
    uint32_t nin = 0, na = 0;
    if (!rd(f, &op.opcode) || !rd(f, &nin)) return die();
    op.ins.resize(nin);
    if (nin && !read_exact(f, op.ins.data(), nin * 4)) return die();
    if (!rd(f, &op.out) || !rd(f, &na)) return die();
    op.attrs.resize(na);
    if (na && !read_exact(f, op.attrs.data(), na * 8)) return die();
  }
  fclose(f);
  if (!validate_program(*p)) {
    delete p;
    return nullptr;
  }
  return p;
}

// right-aligned numpy broadcast: each input dim must be 1 or equal the
// out dim, and the out dim must be exactly the broadcast of the two
bool bcast_ok(const TensorMeta& ma, const TensorMeta& mb,
              const TensorMeta& mo) {
  int rank = static_cast<int>(mo.dims.size());
  if (static_cast<int>(ma.dims.size()) > rank ||
      static_cast<int>(mb.dims.size()) > rank)
    return false;
  for (int i = 0; i < rank; ++i) {
    int ia = i - (rank - static_cast<int>(ma.dims.size()));
    int ib = i - (rank - static_cast<int>(mb.dims.size()));
    int64_t da = ia >= 0 ? ma.dims[ia] : 1;
    int64_t db = ib >= 0 ? mb.dims[ib] : 1;
    if (da != 1 && da != mo.dims[i]) return false;
    if (db != 1 && db != mo.dims[i]) return false;
    if (mo.dims[i] != (da != 1 ? da : db) && !(da == 1 && db == 1))
      return false;
    if (da == 1 && db == 1 && mo.dims[i] != 1) return false;
  }
  return true;
}

// Reject malformed/corrupt programs BEFORE execution: every tensor id in
// bounds, ranks within the executor's fixed-size index arrays, per-opcode
// arity/attr counts, per-op SHAPE consistency (element counts, matmul
// dims, concat sums, gather widths, broadcast compatibility), and
// def-before-use of every op input — a model file from disk must never be
// able to drive out-of-bounds indexing, a null ptr[] deref, or an
// overflowed memcpy (ADVICE r4).
bool validate_program(const Program& p) {
  const size_t nt = p.tensors.size();
  for (const auto& t : p.tensors)
    if (!sane_dims(t)) return false;
  std::vector<char> is_const(nt, 0), defined(nt, 0);
  for (const auto& c : p.consts) {
    if (c.first >= nt) return false;
    is_const[c.first] = 1;
    defined[c.first] = 1;
  }
  for (const auto& in : p.inputs) {
    if (in.first >= nt) return false;
    defined[in.first] = 1;
  }
  for (const auto& op : p.ops) {
    if (op.out >= nt) return false;
    // an op may not clobber a weight const or a program input
    if (is_const[op.out]) return false;
    for (const auto& in : p.inputs)
      if (in.first == op.out) return false;
    for (uint32_t i : op.ins)
      if (i >= nt || !defined[i]) return false;  // def-before-use
    size_t nin = op.ins.size(), na = op.attrs.size();
    const TensorMeta& mo = p.tensors[op.out];
    int out_rank = static_cast<int>(mo.dims.size());
    const TensorMeta* m0 = nin ? &p.tensors[op.ins[0]] : nullptr;
    switch (op.opcode) {
      case ADD: case SUB: case MUL: case DIV: case MAX_: case MIN_:
      case LT: case LE: case GT: case GE: case EQ: case NE:
        if (nin != 2) return false;
        if (!bcast_ok(*m0, p.tensors[op.ins[1]], mo)) return false;
        break;
      case DOT: {
        if (nin != 2) return false;
        const TensorMeta& m1 = p.tensors[op.ins[1]];
        if (m0->dims.size() != 2 || m1.dims.size() != 2 || out_rank != 2)
          return false;
        if (m0->dims[1] != m1.dims[0] || mo.dims[0] != m0->dims[0] ||
            mo.dims[1] != m1.dims[1])
          return false;
        break;
      }
      case EXP: case LOG: case TANH: case LOGISTIC: case RSQRT:
      case SQRT: case NEG: case ABS: case RESHAPE: case IDENT:
      case TRUNC:
        if (nin != 1) return false;
        if (m0->size() != mo.size()) return false;  // elementwise/memcpy
        break;
      case GATHER_ROWS: {
        if (nin != 2) return false;
        const TensorMeta& mi = p.tensors[op.ins[1]];
        if (m0->dims.size() != 2 || out_rank != 2) return false;
        // out rows read idx[0..n), write rows of width table.dims[1]
        if (mo.dims[1] != m0->dims[1]) return false;
        if (mi.size() < mo.dims[0]) return false;
        break;
      }
      case IPOW:
        if (nin != 1 || na != 1) return false;
        if (m0->size() != mo.size()) return false;
        break;
      case BCAST: {
        if (nin != 1 || na != m0->dims.size()) return false;
        std::vector<char> used(out_rank ? out_rank : 1, 0);
        for (size_t i = 0; i < na; ++i) {
          int64_t d = op.attrs[i];
          if (d < 0 || d >= out_rank || used[d]) return false;  // injective
          used[d] = 1;
          // mapped dims must match or broadcast from 1
          if (m0->dims[i] != 1 && m0->dims[i] != mo.dims[d]) return false;
        }
        break;
      }
      case TRANSPOSE: {
        if (nin != 1) return false;
        int in_rank = static_cast<int>(m0->dims.size());
        if (static_cast<int>(na) != in_rank || out_rank != in_rank)
          return false;
        std::vector<char> seen(in_rank, 0);
        for (int i = 0; i < in_rank; ++i) {
          int64_t d = op.attrs[i];
          if (d < 0 || d >= in_rank || seen[d]) return false;  // permutation
          seen[d] = 1;
          if (mo.dims[i] != m0->dims[d]) return false;
        }
        break;
      }
      case RSUM: case RMAX: {
        if (nin != 1) return false;
        int in_rank = static_cast<int>(m0->dims.size());
        std::vector<char> reduced(in_rank ? in_rank : 1, 0);
        for (int64_t ax : op.attrs) {
          if (ax < 0 || ax >= in_rank) return false;
          reduced[ax] = 1;
        }
        int64_t kept = 1;
        for (int i = 0; i < in_rank; ++i)
          if (!reduced[i]) kept *= m0->dims[i];
        if (mo.size() != kept) return false;
        break;
      }
      case CONV2D: {
        if (nin != 2 || na != 6) return false;
        const TensorMeta& mw = p.tensors[op.ins[1]];
        if (m0->dims.size() != 4 || mw.dims.size() != 4 || out_rank != 4)
          return false;
        // NHWC x HWIO -> NHWC: channel agreement + batch carried through
        if (m0->dims[3] != mw.dims[2] || mo.dims[3] != mw.dims[3] ||
            mo.dims[0] != m0->dims[0])
          return false;
        if (op.attrs[0] < 1 || op.attrs[1] < 1) return false;  // strides
        break;
      }
      case MAXPOOL: case SUMPOOL:
        if (nin != 1 || na != 8) return false;
        if (m0->dims.size() != 4 || out_rank != 4) return false;
        if (mo.dims[0] != m0->dims[0] || mo.dims[3] != m0->dims[3])
          return false;
        if (op.attrs[0] < 1 || op.attrs[1] < 1 || op.attrs[2] < 1 ||
            op.attrs[3] < 1)
          return false;
        break;
      case SELECT_N:
        if (nin != 3) return false;
        for (uint32_t i : op.ins)
          if (p.tensors[i].size() != mo.size()) return false;
        break;
      case CLAMP: {
        if (nin != 3) return false;
        if (p.tensors[op.ins[1]].size() != mo.size()) return false;
        int64_t lo_n = m0->size(), hi_n = p.tensors[op.ins[2]].size();
        if (lo_n != 1 && lo_n != mo.size()) return false;
        if (hi_n != 1 && hi_n != mo.size()) return false;
        break;
      }
      case CONCAT: {
        if (nin < 1 || na != 1 || op.attrs[0] < 0 ||
            op.attrs[0] >= out_rank)
          return false;
        int axis = static_cast<int>(op.attrs[0]);
        int64_t ax_sum = 0;
        for (uint32_t in_t : op.ins) {
          const TensorMeta& mi = p.tensors[in_t];
          if (static_cast<int>(mi.dims.size()) != out_rank) return false;
          for (int i = 0; i < out_rank; ++i)
            if (i != axis && mi.dims[i] != mo.dims[i]) return false;
          ax_sum += mi.dims[axis];
        }
        if (ax_sum != mo.dims[axis]) return false;
        break;
      }
      default:
        return false;
    }
    defined[op.out] = 1;
  }
  for (uint32_t o : p.outputs)
    if (o >= nt || !defined[o]) return false;
  return true;
}

// ---- execution --------------------------------------------------------

// broadcasted binary op: strides of size-1 dims are 0
void binary_op(uint32_t opc, const TensorMeta& ma, const float* a,
               const TensorMeta& mb, const float* b, const TensorMeta& mo,
               float* out) {
  int rank = static_cast<int>(mo.dims.size());
  int64_t sa[kMaxRank] = {0}, sb[kMaxRank] = {0}, dims[kMaxRank] = {0};
  // right-align shapes, compute strides (0 where broadcasting)
  int64_t stride = 1;
  std::vector<int64_t> fa(rank, 1), fb(rank, 1);
  int off_a = rank - static_cast<int>(ma.dims.size());
  int off_b = rank - static_cast<int>(mb.dims.size());
  for (int i = 0; i < static_cast<int>(ma.dims.size()); ++i)
    fa[off_a + i] = ma.dims[i];
  for (int i = 0; i < static_cast<int>(mb.dims.size()); ++i)
    fb[off_b + i] = mb.dims[i];
  stride = 1;
  for (int i = rank - 1; i >= 0; --i) {
    dims[i] = mo.dims[i];
    sa[i] = (fa[i] == 1) ? 0 : stride;
    stride *= fa[i];
  }
  stride = 1;
  for (int i = rank - 1; i >= 0; --i) {
    sb[i] = (fb[i] == 1) ? 0 : stride;
    stride *= fb[i];
  }
  int64_t n = mo.size();
  int64_t idx[kMaxRank] = {0};
  for (int64_t lin = 0; lin < n; ++lin) {
    int64_t ia = 0, ib = 0;
    for (int i = 0; i < rank; ++i) {
      ia += idx[i] * sa[i];
      ib += idx[i] * sb[i];
    }
    float x = a[ia], y = b[ib], r = 0;
    switch (opc) {
      case ADD: r = x + y; break;
      case SUB: r = x - y; break;
      case MUL: r = x * y; break;
      case DIV: r = x / y; break;
      case MAX_: r = x > y ? x : y; break;
      case MIN_: r = x < y ? x : y; break;
      case LT: r = x < y ? 1.0f : 0.0f; break;
      case LE: r = x <= y ? 1.0f : 0.0f; break;
      case GT: r = x > y ? 1.0f : 0.0f; break;
      case GE: r = x >= y ? 1.0f : 0.0f; break;
      case EQ: r = x == y ? 1.0f : 0.0f; break;
      case NE: r = x != y ? 1.0f : 0.0f; break;
    }
    out[lin] = r;
    for (int i = rank - 1; i >= 0; --i) {
      if (++idx[i] < dims[i]) break;
      idx[i] = 0;
    }
  }
}

struct Executor {
  const Program& p;
  // storage for computed tensors + bound inputs; consts are read IN PLACE
  // from the Program (no per-inference weight copy) via the ptr view
  std::vector<std::vector<float>> buf;
  std::vector<const float*> ptr;

  explicit Executor(const Program& prog)
      : p(prog), buf(prog.tensors.size()),
        ptr(prog.tensors.size(), nullptr) {
    for (const auto& c : p.consts) ptr[c.first] = c.second.data();
  }

  void bind(uint32_t tid, const float* data, size_t n) {
    buf[tid].assign(data, data + n);
    ptr[tid] = buf[tid].data();
  }

  const TensorMeta& meta(uint32_t t) const { return p.tensors[t]; }

  bool run() {
    for (const auto& op : p.ops) {
      const TensorMeta& mo = meta(op.out);
      std::vector<float>& out = buf[op.out];
      out.assign(static_cast<size_t>(mo.size()), 0.0f);
      const float* a = op.ins.empty() ? nullptr : ptr[op.ins[0]];
      switch (op.opcode) {
        case ADD: case SUB: case MUL: case DIV: case MAX_: case MIN_:
        case LT: case LE: case GT: case GE: case EQ: case NE:
          binary_op(op.opcode, meta(op.ins[0]), a, meta(op.ins[1]),
                    ptr[op.ins[1]], mo, out.data());
          break;
        case TRUNC:
          for (int64_t i = 0; i < mo.size(); ++i) out[i] = truncf(a[i]);
          break;
        case GATHER_ROWS: {
          // embedding lookup: [V, D] table, [N, 1] indices (f32-held
          // ints) -> [N, D]; out-of-range rows fill 0 (FILL_OR_DROP)
          const TensorMeta& mt = meta(op.ins[0]);
          int64_t v = mt.dims[0], dcols = mt.dims[1];
          int64_t n = mo.dims[0];
          const float* idx = ptr[op.ins[1]];
          for (int64_t i = 0; i < n; ++i) {
            int64_t row = static_cast<int64_t>(idx[i]);
            if (row < 0 || row >= v) continue;  // already zero-filled
            std::memcpy(out.data() + i * dcols, a + row * dcols,
                        dcols * 4);
          }
          break;
        }
        case EXP: for (int64_t i = 0; i < mo.size(); ++i) out[i] = std::exp(a[i]); break;
        case LOG: for (int64_t i = 0; i < mo.size(); ++i) out[i] = std::log(a[i]); break;
        case TANH: for (int64_t i = 0; i < mo.size(); ++i) out[i] = std::tanh(a[i]); break;
        case LOGISTIC:
          for (int64_t i = 0; i < mo.size(); ++i)
            out[i] = 1.0f / (1.0f + std::exp(-a[i]));
          break;
        case RSQRT: for (int64_t i = 0; i < mo.size(); ++i) out[i] = 1.0f / std::sqrt(a[i]); break;
        case SQRT: for (int64_t i = 0; i < mo.size(); ++i) out[i] = std::sqrt(a[i]); break;
        case NEG: for (int64_t i = 0; i < mo.size(); ++i) out[i] = -a[i]; break;
        case ABS: for (int64_t i = 0; i < mo.size(); ++i) out[i] = std::fabs(a[i]); break;
        case IPOW: {
          int64_t y = op.attrs[0];
          for (int64_t i = 0; i < mo.size(); ++i)
            out[i] = std::pow(a[i], static_cast<float>(y));
          break;
        }
        case IDENT:
          std::memcpy(out.data(), a, out.size() * 4);
          break;
        case DOT: {
          const TensorMeta& m1 = meta(op.ins[0]);
          const TensorMeta& m2 = meta(op.ins[1]);
          if (m1.dims.size() != 2 || m2.dims.size() != 2) return false;
          int64_t M = m1.dims[0], K = m1.dims[1], N = m2.dims[1];
          const float* b = ptr[op.ins[1]];
          for (int64_t i = 0; i < M; ++i)
            for (int64_t k = 0; k < K; ++k) {
              float av = a[i * K + k];
              if (av == 0.0f) continue;
              const float* brow = b + k * N;
              float* orow = out.data() + i * N;
              for (int64_t j = 0; j < N; ++j) orow[j] += av * brow[j];
            }
          break;
        }
        case BCAST: {
          const TensorMeta& mi = meta(op.ins[0]);
          int rank = static_cast<int>(mo.dims.size());
          // input dim i maps to out dim attrs[i]
          int64_t istrides[kMaxRank] = {0};
          int64_t s = 1;
          std::vector<int64_t> in_strides(mi.dims.size());
          for (int i = static_cast<int>(mi.dims.size()) - 1; i >= 0; --i) {
            in_strides[i] = s;
            s *= mi.dims[i];
          }
          for (int i = 0; i < rank; ++i) istrides[i] = 0;
          for (size_t i = 0; i < op.attrs.size(); ++i) {
            int od = static_cast<int>(op.attrs[i]);
            istrides[od] = (mi.dims[i] == 1) ? 0 : in_strides[i];
          }
          int64_t idx[kMaxRank] = {0};
          for (int64_t lin = 0; lin < mo.size(); ++lin) {
            int64_t ia = 0;
            for (int i = 0; i < rank; ++i) ia += idx[i] * istrides[i];
            out[lin] = a[ia];
            for (int i = rank - 1; i >= 0; --i) {
              if (++idx[i] < mo.dims[i]) break;
              idx[i] = 0;
            }
          }
          break;
        }
        case RESHAPE:
          std::memcpy(out.data(), a, out.size() * 4);
          break;
        case TRANSPOSE: {
          const TensorMeta& mi = meta(op.ins[0]);
          int rank = static_cast<int>(mi.dims.size());
          int64_t in_strides[kMaxRank], perm_strides[kMaxRank];
          int64_t s = 1;
          for (int i = rank - 1; i >= 0; --i) {
            in_strides[i] = s;
            s *= mi.dims[i];
          }
          for (int i = 0; i < rank; ++i)
            perm_strides[i] = in_strides[op.attrs[i]];
          int64_t idx[kMaxRank] = {0};
          for (int64_t lin = 0; lin < mo.size(); ++lin) {
            int64_t ia = 0;
            for (int i = 0; i < rank; ++i) ia += idx[i] * perm_strides[i];
            out[lin] = a[ia];
            for (int i = rank - 1; i >= 0; --i) {
              if (++idx[i] < mo.dims[i]) break;
              idx[i] = 0;
            }
          }
          break;
        }
        case RSUM: case RMAX: {
          const TensorMeta& mi = meta(op.ins[0]);
          int rank = static_cast<int>(mi.dims.size());
          bool reduced[kMaxRank] = {false};
          for (int64_t ax : op.attrs) reduced[ax] = true;
          int64_t out_strides[kMaxRank] = {0};
          // strides in the OUT tensor for each kept in-dim
          int64_t s = 1;
          for (int i = rank - 1; i >= 0; --i) {
            if (!reduced[i]) {
              out_strides[i] = s;
              s *= mi.dims[i];
            }
          }
          if (op.opcode == RMAX)
            out.assign(out.size(),
                       -std::numeric_limits<float>::infinity());
          int64_t idx[kMaxRank] = {0};
          for (int64_t lin = 0; lin < mi.size(); ++lin) {
            int64_t io = 0;
            for (int i = 0; i < rank; ++i)
              if (!reduced[i]) io += idx[i] * out_strides[i];
            if (op.opcode == RSUM) out[io] += a[lin];
            else out[io] = out[io] > a[lin] ? out[io] : a[lin];
            for (int i = rank - 1; i >= 0; --i) {
              if (++idx[i] < mi.dims[i]) break;
              idx[i] = 0;
            }
          }
          break;
        }
        case CONV2D: {
          const TensorMeta& mx = meta(op.ins[0]);
          const TensorMeta& mw = meta(op.ins[1]);
          const float* w = ptr[op.ins[1]];
          int64_t sh = op.attrs[0], sw = op.attrs[1];
          int64_t pt = op.attrs[2], pl = op.attrs[4];
          int64_t N = mx.dims[0], H = mx.dims[1], W = mx.dims[2],
                  C = mx.dims[3];
          int64_t KH = mw.dims[0], KW = mw.dims[1], CO = mw.dims[3];
          int64_t OH = mo.dims[1], OW = mo.dims[2];
          for (int64_t n = 0; n < N; ++n)
            for (int64_t oy = 0; oy < OH; ++oy)
              for (int64_t ox = 0; ox < OW; ++ox) {
                float* opix = out.data() + ((n * OH + oy) * OW + ox) * CO;
                for (int64_t ky = 0; ky < KH; ++ky) {
                  int64_t iy = oy * sh + ky - pt;
                  if (iy < 0 || iy >= H) continue;
                  for (int64_t kx = 0; kx < KW; ++kx) {
                    int64_t ix = ox * sw + kx - pl;
                    if (ix < 0 || ix >= W) continue;
                    const float* ipix =
                        a + ((n * H + iy) * W + ix) * C;
                    const float* wrow = w + (ky * KW + kx) * C * CO;
                    for (int64_t c = 0; c < C; ++c) {
                      float xv = ipix[c];
                      if (xv == 0.0f) continue;
                      const float* wv = wrow + c * CO;
                      for (int64_t co = 0; co < CO; ++co)
                        opix[co] += xv * wv[co];
                    }
                  }
                }
              }
          break;
        }
        case MAXPOOL: case SUMPOOL: {
          const TensorMeta& mx = meta(op.ins[0]);
          int64_t wh = op.attrs[0], ww = op.attrs[1];
          int64_t sh = op.attrs[2], sw = op.attrs[3];
          int64_t pt = op.attrs[4], pl = op.attrs[6];
          int64_t N = mx.dims[0], H = mx.dims[1], W = mx.dims[2],
                  C = mx.dims[3];
          int64_t OH = mo.dims[1], OW = mo.dims[2];
          bool is_max = op.opcode == MAXPOOL;
          if (is_max)
            out.assign(out.size(),
                       -std::numeric_limits<float>::infinity());
          for (int64_t n = 0; n < N; ++n)
            for (int64_t oy = 0; oy < OH; ++oy)
              for (int64_t ox = 0; ox < OW; ++ox) {
                float* opix = out.data() + ((n * OH + oy) * OW + ox) * C;
                for (int64_t ky = 0; ky < wh; ++ky) {
                  int64_t iy = oy * sh + ky - pt;
                  if (iy < 0 || iy >= H) continue;
                  for (int64_t kx = 0; kx < ww; ++kx) {
                    int64_t ix = ox * sw + kx - pl;
                    if (ix < 0 || ix >= W) continue;
                    const float* ipix = a + ((n * H + iy) * W + ix) * C;
                    for (int64_t c = 0; c < C; ++c) {
                      if (is_max)
                        opix[c] = opix[c] > ipix[c] ? opix[c] : ipix[c];
                      else
                        opix[c] += ipix[c];
                    }
                  }
                }
              }
          break;
        }
        case SELECT_N: {
          const float* t1 = ptr[op.ins[1]];
          const float* t2 = ptr[op.ins[2]];
          for (int64_t i = 0; i < mo.size(); ++i)
            out[i] = (a[i] != 0.0f) ? t2[i] : t1[i];
          break;
        }
        case CLAMP: {
          const float* lo = a;
          const float* x = ptr[op.ins[1]];
          const float* hi = ptr[op.ins[2]];
          bool lo_scalar = meta(op.ins[0]).size() == 1;
          bool hi_scalar = meta(op.ins[2]).size() == 1;
          for (int64_t i = 0; i < mo.size(); ++i) {
            float l = lo_scalar ? lo[0] : lo[i];
            float h = hi_scalar ? hi[0] : hi[i];
            float v = x[i];
            out[i] = v < l ? l : (v > h ? h : v);
          }
          break;
        }
        case CONCAT: {
          int axis = static_cast<int>(op.attrs[0]);
          int rank = static_cast<int>(mo.dims.size());
          int64_t outer = 1, inner = 1;
          for (int i = 0; i < axis; ++i) outer *= mo.dims[i];
          for (int i = axis + 1; i < rank; ++i) inner *= mo.dims[i];
          int64_t out_ax = mo.dims[axis];
          int64_t ax_off = 0;
          for (uint32_t in_t : op.ins) {
            const TensorMeta& mi = meta(in_t);
            const float* src = ptr[in_t];
            int64_t ax_n = mi.dims[axis];
            for (int64_t o = 0; o < outer; ++o)
              std::memcpy(
                  out.data() + (o * out_ax + ax_off) * inner,
                  src + o * ax_n * inner, ax_n * inner * 4);
            ax_off += ax_n;
          }
          break;
        }
        default:
          return false;
      }
      ptr[op.out] = out.data();
    }
    return true;
  }
};

}  // namespace

extern "C" {

void* ptpu_aot_load(const char* path) { return load_program(path); }

// Multi-instance serving with ONE weight copy — the reference's
// paddle_gradient_machine_create_shared_param (capi/gradient_machine.h:88)
// analog: the returned handle shares the origin's Program (weights are
// read in place; each ptpu_aot_infer builds its own activation buffers),
// so any number of threads may infer concurrently through any mix of
// origin/shared handles. Each handle must be released; the weights are
// freed on the last release, in any order.
void* ptpu_aot_create_shared(void* origin) {
  auto* p = static_cast<Program*>(origin);
  if (!p) return nullptr;
  p->refs.fetch_add(1, std::memory_order_relaxed);
  return p;
}

// Same calling convention as capi.cpp's ptpu_infer: single dense float
// input by name, first output copied out. Returns 0 ok, -2 capacity (with
// required shape in out_rows/out_cols), -3 shape mismatch, -1 failure.
int ptpu_aot_infer(void* handle, const char* input_name, const float* data,
                   int64_t batch, int64_t dim, float* out,
                   int64_t out_capacity, int64_t* out_rows,
                   int64_t* out_cols) {
  auto* p = static_cast<Program*>(handle);
  if (!p) return -1;
  // v1 contract: exactly ONE input (export_aot_program enforces the same
  // at export time) — refusing multi-input programs here means a caller
  // can never get rc=0 with an unbound, silently-zeroed feed
  if (p->inputs.size() != 1 || p->outputs.empty()) return -4;
  const auto& in = p->inputs[0];
  if (in.second != input_name) return -4;
  const TensorMeta& m = p->tensors[in.first];
  // rank-2 [batch, dim] dense feed, or rank-1 [batch] integer-id feed
  // (ids passed as floats, dim == 1)
  bool shape_ok =
      (m.dims.size() == 2 && m.dims[0] == batch && m.dims[1] == dim) ||
      (m.dims.size() == 1 && m.dims[0] == batch && dim == 1);
  if (!shape_ok)
    return -3;  // program was AOT-compiled for a fixed shape
  Executor ex(*p);
  ex.bind(in.first, data, static_cast<size_t>(batch * dim));
  if (!ex.run()) return -1;
  const TensorMeta& mo = p->tensors[p->outputs[0]];
  int64_t rows = mo.dims.empty() ? 1 : mo.dims[0];
  int64_t cols = mo.size() / (rows ? rows : 1);
  *out_rows = rows;
  *out_cols = cols;
  if (rows * cols > out_capacity) return -2;
  std::memcpy(out, ex.ptr[p->outputs[0]], rows * cols * 4);
  return 0;
}

void ptpu_aot_release(void* handle) {
  auto* p = static_cast<Program*>(handle);
  if (p && p->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete p;
}

}  // extern "C"
