// Async shuffling record pool — the native data-loader.
//
// Reference analog: PyDataProvider2's async pool thread filling a shuffle
// buffer ahead of the trainer (gserver/dataproviders/PyDataProvider2.cpp:
// 195,334-400) and DataProvider's double-buffered getNextBatch
// (DataProvider.h:292). A background producer thread streams records from
// recordio files into a bounded shuffle buffer; the consumer draws
// uniformly from the buffer (the classic shuffle-window), overlapping disk
// IO with device compute.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "recordio_format.h"

using ptn::read_u64;

namespace {

struct Pool {
  std::vector<std::string> paths;
  size_t window;
  std::mt19937_64 rng;

  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::vector<std::string> buffer;   // shuffle window
  bool producer_done = false;
  bool stop = false;
  bool error = false;                // unopenable file / corrupt record
  std::thread producer;

  // handed-out record storage (stable address until next pop)
  std::string current;

  void produce() {
    for (const auto& path : paths) {
      FILE* f = fopen(path.c_str(), "rb");
      if (!f) {
        // a missing file must fail loudly, not shrink the dataset
        std::lock_guard<std::mutex> lk(mu);
        error = true;
        break;
      }
      fseek(f, 0, SEEK_END);
      const uint64_t file_size = static_cast<uint64_t>(ftell(f));
      fseek(f, 0, SEEK_SET);
      uint64_t len = 0;
      while (read_u64(f, &len)) {
        if (len > file_size) {  // corrupt length prefix: don't alloc 2^63
          std::lock_guard<std::mutex> lk(mu);
          error = true;
          break;
        }
        std::string rec(len, '\0');
        if (len && fread(&rec[0], 1, len, f) != len) {
          // truncated payload: fail loudly like the corrupt-length path
          std::lock_guard<std::mutex> lk(mu);
          error = true;
          break;
        }
        {
          std::unique_lock<std::mutex> lk(mu);
          not_full.wait(lk, [&] { return buffer.size() < window || stop; });
          if (stop) {
            fclose(f);
            return;
          }
          buffer.push_back(std::move(rec));
        }
        not_empty.notify_one();
      }
      fclose(f);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (error) break;
      }
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      producer_done = true;
    }
    not_empty.notify_all();
  }
};

}  // namespace

extern "C" {

void* ptn_pool_create(const char** paths, uint64_t n_paths, uint64_t window,
                      uint64_t seed) {
  auto* p = new Pool();
  for (uint64_t i = 0; i < n_paths; ++i) p->paths.emplace_back(paths[i]);
  p->window = window < 1 ? 1 : window;
  p->rng.seed(seed);
  p->producer = std::thread([p] { p->produce(); });
  return p;
}

// Pops one record (uniform over the current shuffle window).
// Returns 1 with (*data,*len) set, 0 at end of data, -1 on IO error
// (missing file / corrupt record stream).
// The pointer stays valid until the next ptn_pool_next / destroy.
int ptn_pool_next(void* handle, const char** data, uint64_t* len) {
  auto* p = static_cast<Pool*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] { return !p->buffer.empty() || p->producer_done; });
  if (p->buffer.empty()) return p->error ? -1 : 0;
  size_t i = p->rng() % p->buffer.size();
  std::swap(p->buffer[i], p->buffer.back());
  p->current = std::move(p->buffer.back());
  p->buffer.pop_back();
  lk.unlock();
  p->not_full.notify_one();
  *data = p->current.data();
  *len = p->current.size();
  return 1;
}

void ptn_pool_destroy(void* handle) {
  auto* p = static_cast<Pool*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->not_full.notify_all();
  if (p->producer.joinable()) p->producer.join();
  delete p;
}

}  // extern "C"
