// C inference over the PJRT C API — the TPU-production path.
//
// Reference analog: paddle/capi driving the C++ engine on device
// (capi/gradient_machine.h:36-112). Here the engine is the platform's
// PJRT plugin (libtpu.so on TPU hosts; any GetPjrtApi .so works): the
// .ptpj artifact (export.export_pjrt_model) carries the StableHLO module
// with weights baked in + serialized CompileOptions, this file dlopens
// the plugin, compiles, and executes — no Python, no jax, no XLA linked
// into the embedder's process. SURVEY §7 item 11 ("C ABI over PJRT").
//
// Sibling paths: aot_runtime.cpp (CPU embedded, no plugin needed),
// capi.cpp (embedded CPython, full graph coverage).
//
// NOTE: on this build machine the only GetPjrtApi provider is libtpu.so
// and the TPU is reachable only through the axon relay (not libtpu), so
// CI exercises plugin loading, artifact parsing, API versioning, and the
// graceful-failure path; the execute path runs on real TPU hosts
// (ptpu_pjrt self-test gated by PTPU_PJRT_PLUGIN).

#include <dlfcn.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

thread_local std::string g_last_error;

void set_error(std::string msg) { g_last_error = std::move(msg); }

// consume + destroy a PJRT_Error; returns true if there WAS an error
bool take_error(const PJRT_Api* api, PJRT_Error* err, const char* where) {
  if (err == nullptr) return false;
  PJRT_Error_Message_Args margs;
  memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  set_error(std::string(where) + ": " +
            std::string(margs.message, margs.message_size));
  PJRT_Error_Destroy_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return true;
}

bool await_event(const PJRT_Api* api, PJRT_Event* ev, const char* where) {
  if (!ev) return true;
  PJRT_Event_Await_Args aargs;
  memset(&aargs, 0, sizeof(aargs));
  aargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aargs.event = ev;
  PJRT_Error* err = api->PJRT_Event_Await(&aargs);
  PJRT_Event_Destroy_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  dargs.event = ev;
  api->PJRT_Event_Destroy(&dargs);
  return !take_error(api, err, where);
}

struct InputSpec {
  std::string name;
  uint8_t dtype = 0;  // 0 = f32, 1 = i32
  uint8_t rank = 2;   // 1 ([batch]) or 2 ([batch, dim])
  int64_t batch = 0;
  int64_t dim = 0;    // 1 for rank-1 specs
};

struct Model {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_LoadedExecutable* exec = nullptr;
  size_t num_outputs = 0;
  std::vector<InputSpec> inputs;
  // shared-param instances (ptpu_pjrt_create_shared) hold the same Model
  // (one compiled executable, weights baked in on device once)
  std::atomic<int> refs{1};
};

bool read_exact(FILE* f, void* dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

template <typename T>
bool rd(FILE* f, T* v) { return read_exact(f, v, sizeof(T)); }

// Parse the .ptpj container (export.export_pjrt_model).
bool parse_ptpj(const char* path, std::vector<InputSpec>* inputs,
                uint32_t* n_outputs, std::string* mlir, std::string* opts) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open ") + path);
    return false;
  }
  auto fail = [&](const char* why) {
    set_error(std::string("bad .ptpj: ") + why);
    fclose(f);
    return false;
  };
  char magic[4];
  uint32_t version = 0, ni = 0;
  if (!read_exact(f, magic, 4) || memcmp(magic, "PTPJ", 4) != 0)
    return fail("magic");
  if (!rd(f, &version) || (version != 1 && version != 2))
    return fail("version");
  if (!rd(f, &ni)) return fail("inputs");
  for (uint32_t i = 0; i < ni; ++i) {
    uint16_t nl = 0;
    if (!rd(f, &nl)) return fail("name len");
    InputSpec spec;
    spec.name.resize(nl);
    if (nl && !read_exact(f, spec.name.data(), nl)) return fail("name");
    uint8_t dtype = 0, rank = 0;
    if (!rd(f, &dtype) || !rd(f, &rank)) return fail("spec");
    // v1 artifacts only ever declared f32 rank-2; v2 adds i32 rank-1
    // (integer/embedding feeds) so the spec matches the module signature
    if (version == 1 && (dtype != 0 || rank != 2)) return fail("spec");
    if (dtype > 1 || rank < 1 || rank > 2) return fail("spec");
    spec.dtype = dtype;
    spec.rank = rank;
    if (rank == 2) {
      int64_t dims[2];
      if (!read_exact(f, dims, sizeof(dims))) return fail("dims");
      spec.batch = dims[0];
      spec.dim = dims[1];
    } else {
      int64_t d0 = 0;
      if (!rd(f, &d0)) return fail("dims");
      spec.batch = d0;
      spec.dim = 1;
    }
    inputs->push_back(std::move(spec));
  }
  if (!rd(f, n_outputs)) return fail("outputs");
  uint64_t mlir_len = 0, opts_len = 0;
  if (!rd(f, &mlir_len)) return fail("mlir len");
  mlir->resize(mlir_len);
  if (mlir_len && !read_exact(f, mlir->data(), mlir_len))
    return fail("mlir");
  if (!rd(f, &opts_len)) return fail("opts len");
  opts->resize(opts_len);
  if (opts_len && !read_exact(f, opts->data(), opts_len))
    return fail("opts");
  fclose(f);
  return true;
}

void destroy_model(Model* m) {
  if (!m) return;
  if (m->api) {
    if (m->exec) {
      PJRT_LoadedExecutable_Destroy_Args args;
      memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
      args.executable = m->exec;
      take_error(m->api, m->api->PJRT_LoadedExecutable_Destroy(&args),
                 "exec destroy");
    }
    if (m->client) {
      PJRT_Client_Destroy_Args args;
      memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
      args.client = m->client;
      take_error(m->api, m->api->PJRT_Client_Destroy(&args),
                 "client destroy");
    }
  }
  if (m->dl) dlclose(m->dl);
  delete m;
}

}  // namespace

extern "C" {

const char* ptpu_pjrt_last_error(void) { return g_last_error.c_str(); }

// Load plugin + artifact, create the client, compile the module.
// Returns a handle or nullptr (ptpu_pjrt_last_error explains).
void* ptpu_pjrt_load(const char* model_path, const char* plugin_path) {
  auto* m = new Model();
  m->dl = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (!m->dl) {
    set_error(std::string("dlopen ") + plugin_path + ": " + dlerror());
    destroy_model(m);
    return nullptr;
  }
  using GetApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(m->dl, "GetPjrtApi"));
  if (!get_api) {
    set_error("plugin exports no GetPjrtApi");
    destroy_model(m);
    return nullptr;
  }
  m->api = get_api();
  if (!m->api || m->api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    set_error("PJRT API major version mismatch");
    destroy_model(m);
    return nullptr;
  }

  std::string mlir, opts;
  uint32_t n_outputs = 0;
  if (!parse_ptpj(model_path, &m->inputs, &n_outputs, &mlir, &opts)) {
    destroy_model(m);
    return nullptr;
  }

  {
    PJRT_Plugin_Initialize_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    if (take_error(m->api, m->api->PJRT_Plugin_Initialize(&args),
                   "plugin init")) {
      destroy_model(m);
      return nullptr;
    }
  }
  {
    PJRT_Client_Create_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
    if (take_error(m->api, m->api->PJRT_Client_Create(&args),
                   "client create")) {
      destroy_model(m);
      return nullptr;
    }
    m->client = args.client;
  }
  {
    PJRT_Program program;
    memset(&program, 0, sizeof(program));
    program.struct_size = PJRT_Program_STRUCT_SIZE;
    program.code = mlir.data();
    program.code_size = mlir.size();
    static const char kFormat[] = "mlir";
    program.format = kFormat;
    program.format_size = sizeof(kFormat) - 1;

    PJRT_Client_Compile_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
    args.client = m->client;
    args.program = &program;
    args.compile_options = opts.data();
    args.compile_options_size = opts.size();
    if (take_error(m->api, m->api->PJRT_Client_Compile(&args), "compile")) {
      destroy_model(m);
      return nullptr;
    }
    m->exec = args.executable;
  }
  {
    PJRT_LoadedExecutable_GetExecutable_Args gargs;
    memset(&gargs, 0, sizeof(gargs));
    gargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    gargs.loaded_executable = m->exec;
    if (take_error(m->api, m->api->PJRT_LoadedExecutable_GetExecutable(&gargs),
                   "get executable")) {
      destroy_model(m);
      return nullptr;
    }
    PJRT_Executable_NumOutputs_Args nargs;
    memset(&nargs, 0, sizeof(nargs));
    nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    nargs.executable = gargs.executable;
    if (take_error(m->api, m->api->PJRT_Executable_NumOutputs(&nargs),
                   "num outputs")) {
      destroy_model(m);
      return nullptr;
    }
    m->num_outputs = nargs.num_outputs;
    PJRT_Executable_Destroy_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Executable_Destroy_Args_STRUCT_SIZE;
    dargs.executable = gargs.executable;
    take_error(m->api, m->api->PJRT_Executable_Destroy(&dargs),
               "executable destroy");
  }
  return m;
}

}  // extern "C"

namespace {

// Shared single-input execute path. 0 ok, -2 capacity, -3 shape/dtype
// mismatch, -4 contract (not single-input / wrong name), -1 runtime
// failure.
int pjrt_infer_impl(Model* m, const char* input_name, const void* data,
                    uint8_t dtype_code, int64_t batch, int64_t dim,
                    float* out, int64_t out_capacity, int64_t* out_rows,
                    int64_t* out_cols) {
  if (!m || !m->exec) return -1;
  if (m->inputs.size() != 1 || m->inputs[0].name != input_name) return -4;
  const InputSpec& spec = m->inputs[0];
  if (spec.dtype != dtype_code) return -3;
  if (spec.batch != batch || spec.dim != dim) return -3;

  const PJRT_Api* api = m->api;
  // addressable device 0
  PJRT_Device* device = nullptr;
  {
    PJRT_Client_AddressableDevices_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    args.client = m->client;
    if (take_error(api, api->PJRT_Client_AddressableDevices(&args),
                   "addressable devices"))
      return -1;
    if (args.num_addressable_devices == 0) {
      set_error("no addressable devices");
      return -1;
    }
    device = args.addressable_devices[0];
  }

  // host -> device
  PJRT_Buffer* in_buf = nullptr;
  {
    int64_t dims[2] = {batch, dim};
    PJRT_Client_BufferFromHostBuffer_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    args.client = m->client;
    args.data = data;
    args.type = spec.dtype == 1 ? PJRT_Buffer_Type_S32 : PJRT_Buffer_Type_F32;
    args.dims = dims;
    args.num_dims = spec.rank;
    args.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    args.device = device;
    if (take_error(api, api->PJRT_Client_BufferFromHostBuffer(&args),
                   "h2d"))
      return -1;
    in_buf = args.buffer;
    if (!await_event(api, args.done_with_host_buffer, "h2d event")) {
      // fallthrough: buffer still destroyed below on error path
    }
  }

  auto destroy_buffer = [&](PJRT_Buffer* b) {
    if (!b) return;
    PJRT_Buffer_Destroy_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    args.buffer = b;
    take_error(api, api->PJRT_Buffer_Destroy(&args), "buffer destroy");
  };

  // execute
  std::vector<PJRT_Buffer*> outputs(m->num_outputs, nullptr);
  {
    PJRT_ExecuteOptions opts;
    memset(&opts, 0, sizeof(opts));
    opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
    PJRT_Buffer* const arg_list[] = {in_buf};
    PJRT_Buffer* const* const arg_lists[] = {arg_list};
    PJRT_Buffer** output_lists[] = {outputs.data()};
    PJRT_Event* done = nullptr;

    PJRT_LoadedExecutable_Execute_Args args;
    memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    args.executable = m->exec;
    args.options = &opts;
    args.argument_lists = arg_lists;
    args.num_devices = 1;
    args.num_args = 1;
    args.output_lists = output_lists;
    args.device_complete_events = &done;
    args.execute_device = device;
    if (take_error(api, api->PJRT_LoadedExecutable_Execute(&args),
                   "execute")) {
      destroy_buffer(in_buf);
      return -1;
    }
    if (!await_event(api, done, "execute event")) {
      destroy_buffer(in_buf);
      for (auto* b : outputs) destroy_buffer(b);
      return -1;
    }
  }
  destroy_buffer(in_buf);

  // first output -> host
  int rc = -1;
  {
    PJRT_Buffer* out_buf = outputs[0];
    PJRT_Buffer_Dimensions_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
    dargs.buffer = out_buf;
    if (!take_error(api, api->PJRT_Buffer_Dimensions(&dargs), "dims")) {
      int64_t rows = dargs.num_dims > 0 ? dargs.dims[0] : 1;
      int64_t total = 1;
      for (size_t i = 0; i < dargs.num_dims; ++i) total *= dargs.dims[i];
      int64_t cols = rows ? total / rows : total;
      *out_rows = rows;
      *out_cols = cols;
      if (total > out_capacity) {
        rc = -2;
      } else {
        PJRT_Buffer_ToHostBuffer_Args targs;
        memset(&targs, 0, sizeof(targs));
        targs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
        targs.src = out_buf;
        targs.dst = out;
        targs.dst_size = static_cast<size_t>(total) * sizeof(float);
        if (!take_error(api, api->PJRT_Buffer_ToHostBuffer(&targs), "d2h") &&
            await_event(api, targs.event, "d2h event"))
          rc = 0;
      }
    }
  }
  for (auto* b : outputs) destroy_buffer(b);
  return rc;
}

}  // namespace

extern "C" {

// Single dense f32 input by name → first output, same convention as
// ptpu_infer/ptpu_aot_infer.
int ptpu_pjrt_infer(void* handle, const char* input_name, const float* data,
                    int64_t batch, int64_t dim, float* out,
                    int64_t out_capacity, int64_t* out_rows,
                    int64_t* out_cols) {
  return pjrt_infer_impl(static_cast<Model*>(handle), input_name, data, 0,
                         batch, dim, out, out_capacity, out_rows, out_cols);
}

// Single integer-id input ([batch] i32 — embedding models, .ptpj v2).
int ptpu_pjrt_infer_i32(void* handle, const char* input_name,
                        const int32_t* data, int64_t batch, float* out,
                        int64_t out_capacity, int64_t* out_rows,
                        int64_t* out_cols) {
  return pjrt_infer_impl(static_cast<Model*>(handle), input_name, data, 1,
                         batch, 1, out, out_capacity, out_rows, out_cols);
}

// Shared-param multi-instance serving (gradient_machine.h:88 analog):
// the compiled executable + its on-device weights are shared; PJRT
// execution is reentrant, so any number of threads may infer through any
// mix of handles. Freed on the last release, in any order.
void* ptpu_pjrt_create_shared(void* origin) {
  auto* m = static_cast<Model*>(origin);
  if (!m) return nullptr;
  m->refs.fetch_add(1, std::memory_order_relaxed);
  return m;
}

void ptpu_pjrt_release(void* handle) {
  auto* m = static_cast<Model*>(handle);
  if (m && m->refs.fetch_sub(1, std::memory_order_acq_rel) == 1)
    destroy_model(m);
}

}  // extern "C"
