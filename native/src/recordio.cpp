// Native recordio: length-prefixed record files, C ABI.
//
// Reference analog: the recordio chunk library the Go master partitions
// datasets with (go/master/service.go:106) and the C++ DataProvider file
// readers (gserver/dataproviders/). Format matches
// paddle_tpu/master/recordio.py: per record an 8-byte LE u64 length then
// the payload — Python writes, C++ reads, and vice versa.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "recordio_format.h"

using ptn::read_u64;
using ptn::write_u64;

namespace {

struct Buf {
  std::vector<std::string> records;
};

struct Writer {
  FILE* f = nullptr;
  uint64_t count = 0;
};

}  // namespace

extern "C" {

// ---- writer ---------------------------------------------------------------

void* ptn_write_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int ptn_write_record(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  if (!w || !w->f) return -1;
  if (!write_u64(w->f, len)) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  w->count++;
  return 0;
}

// Returns the record count, or UINT64_MAX if the final flush failed
// (full disk surfaces here — stdio buffers until fclose).
uint64_t ptn_write_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  uint64_t n = w->count;
  int rc = fclose(w->f);
  delete w;
  return rc == 0 ? n : UINT64_MAX;
}

// ---- index ----------------------------------------------------------------

// Returns a malloc'd array of record byte offsets; caller frees with
// ptn_free_offsets. n_out receives the count; returns 0 on success.
int ptn_index(const char* path, uint64_t** offsets_out, uint64_t* n_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<uint64_t> offs;
  uint64_t pos = 0, len = 0;
  while (read_u64(f, &len)) {
    offs.push_back(pos);
    if (fseek(f, static_cast<long>(len), SEEK_CUR) != 0) break;
    pos += 8 + len;
  }
  fclose(f);
  auto* arr = static_cast<uint64_t*>(malloc(offs.size() * sizeof(uint64_t)));
  memcpy(arr, offs.data(), offs.size() * sizeof(uint64_t));
  *offsets_out = arr;
  *n_out = offs.size();
  return 0;
}

void ptn_free_offsets(uint64_t* offsets) { free(offsets); }

// ---- chunk reader ---------------------------------------------------------

void* ptn_read_chunk(const char* path, uint64_t offset, uint64_t count) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  const uint64_t file_size = static_cast<uint64_t>(ftell(f));
  if (fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  auto* buf = new Buf();
  uint64_t len = 0;
  for (uint64_t i = 0; i < count && read_u64(f, &len); ++i) {
    if (len > file_size) break;  // corrupt prefix: no giant allocation
    std::string rec(len, '\0');
    if (len && fread(&rec[0], 1, len, f) != len) break;
    buf->records.push_back(std::move(rec));
  }
  fclose(f);
  return buf;
}

uint64_t ptn_buf_count(void* handle) {
  return static_cast<Buf*>(handle)->records.size();
}

int ptn_buf_get(void* handle, uint64_t i, const char** data_out,
                uint64_t* len_out) {
  auto* buf = static_cast<Buf*>(handle);
  if (i >= buf->records.size()) return -1;
  *data_out = buf->records[i].data();
  *len_out = buf->records[i].size();
  return 0;
}

void ptn_buf_free(void* handle) { delete static_cast<Buf*>(handle); }

}  // extern "C"
